"""Multi-host bootstrap and topology helpers.

The reference scales out through engine clusters whose workers talk
NCCL/MPI-style through Flink/Spark RPC (SURVEY §5 "distributed
communication backend").  The TPU-native counterpart is jax's
distributed runtime: every host runs the same program, devices of all
hosts form ONE global `Mesh`, and XLA inserts ICI/DCN collectives for
the shardings used — nothing in the table format itself needs a
message bus.  This module is the glue:

- `initialize(...)`: `jax.distributed.initialize` with env fallbacks
  (COORDINATOR_ADDRESS / NUM_PROCESSES / PROCESS_ID — the same shape
  torchrun/mpirun environments provide).
- `global_mesh(...)`: a Mesh over every device of every host.
- `process_local_batch(...)`: turn each host's local Arrow/numpy batch
  into one globally-sharded jax.Array
  (`jax.make_array_from_process_local_data`) — the multi-host data
  ingestion path for jax_data loaders.
- `assign_splits(...)`: deterministic scan-split ownership per process
  (the analog of the reference's split enumerator handing splits to
  parallel source readers), byte-size-aware LPT like
  parallel/packing.py so one host never owns all the large splits.
- `barrier(...)` / `broadcast_value(...)` / `allgather_bytes(...)`:
  the small agreement primitives the distributed write plane
  (parallel/distributed.py) builds commit arbitration, pinned-snapshot
  scans and rescale handoffs on.

Everything degrades to single-process: `initialize` is a no-op when
num_processes==1, the mesh covers local devices, split assignment
returns everything, and the agreement primitives return their inputs
without touching a collective.
"""

import os
import time as _time
from typing import List, Optional, Sequence, Tuple

import numpy as np


def peer_death_tolerance(max_missing_heartbeats: Optional[int] = None
                         ) -> dict:
    """Heartbeat-tolerance kwargs for the distributed runtime client
    AND the coordination service, from the explicit argument or the
    `PAIMON_MULTIHOST_PEER_MISSED_HEARTBEATS` env var.  Empty dict
    when neither is set (jax defaults apply: ~10 missed heartbeats at
    10s intervals, after which the coordination service declares the
    quiet task crashed and FATALLY tears down every other task).

    That default contradicts this repo's fleet design: host death is
    an EXPECTED event the lease detector (parallel/maintenance_plane)
    observes and survives — survivors adopt the dead host's groups
    and keep serving.  A mesh that opts in here keeps the survivors'
    processes alive through a peer's death long enough for leases to
    govern, instead of having XLA abort them ~100s in."""
    if max_missing_heartbeats is None:
        env = os.environ.get("PAIMON_MULTIHOST_PEER_MISSED_HEARTBEATS")
        if env:
            max_missing_heartbeats = int(env)
    if max_missing_heartbeats is None:
        return {}
    return {"service_max_missing_heartbeats": max_missing_heartbeats,
            "client_max_missing_heartbeats": max_missing_heartbeats}


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None,
               max_missing_heartbeats: Optional[int] = None
               ) -> Tuple[int, int]:
    """Bring up jax's distributed runtime (multi-host). Arguments
    default from the standard env vars; single-process is a no-op.
    Returns (process_index, process_count).

    `max_missing_heartbeats` (or the
    `PAIMON_MULTIHOST_PEER_MISSED_HEARTBEATS` env var) widens how many
    10s heartbeats a peer may miss before the coordination service
    declares it crashed and aborts the WHOLE mesh — see
    `peer_death_tolerance` for why lease-governed fleets want this."""
    import jax

    coordinator_address = coordinator_address or \
        os.environ.get("COORDINATOR_ADDRESS")
    if num_processes is None:
        num_processes = int(os.environ.get("NUM_PROCESSES", "1"))
    if process_id is None:
        process_id = int(os.environ.get("PROCESS_ID", "0"))
    if num_processes > 1:
        # jax 0.4.x ships the CPU backend with cross-process
        # collectives DISABLED by default — without opting into the
        # Gloo implementation, the first multiprocess computation
        # fails with "Multiprocess computations aren't implemented on
        # the CPU backend" (the long-standing test_multihost_real
        # red).  Harmless on TPU (the setting only affects the CPU
        # backend); must run before the backend initializes.
        try:
            jax.config.update("jax_cpu_collectives_implementation",
                              "gloo")
        except (AttributeError, ValueError, KeyError) as e:
            # other jax versions: the flag may not exist (newer
            # releases enable cross-process CPU collectives through
            # the distributed runtime itself).  NOT silent: a pod that
            # falls back to broken CPU collectives fails much later
            # with an inscrutable "Multiprocess computations aren't
            # implemented" — surface the config miss now so that
            # failure is diagnosable from the warning + metric.
            import warnings

            from paimon_tpu.metrics import (
                MULTIHOST_CONFIG_WARNINGS, global_registry,
            )
            warnings.warn(
                "multihost.initialize: could not opt the CPU backend "
                f"into Gloo cross-process collectives ({e!r}); if "
                "this jax build lacks them, the first cross-process "
                "computation will fail with 'Multiprocess "
                "computations aren't implemented on the CPU backend'",
                RuntimeWarning, stacklevel=2)
            global_registry().multihost_metrics().counter(
                MULTIHOST_CONFIG_WARNINGS).inc()
        tolerance = peer_death_tolerance(max_missing_heartbeats)
        if tolerance:
            # the public wrapper does not forward heartbeat knobs
            # (jax 0.4.x); mirror its one precondition and call the
            # runtime state directly.  A jax build whose internals
            # moved falls back to the default (intolerant) bring-up —
            # NOT silent, same warning+metric contract as the gloo
            # opt-in above: the mesh still comes up, but survivors
            # will be aborted ~100s after a peer dies
            try:
                from jax._src import distributed as _dist
                from jax._src import xla_bridge as _bridge
                if _bridge.backends_are_initialized():
                    raise RuntimeError(
                        "multihost.initialize must run before any JAX "
                        "computation")
                _dist.global_state.initialize(
                    coordinator_address=coordinator_address,
                    num_processes=num_processes,
                    process_id=process_id,
                    **tolerance)
                return jax.process_index(), jax.process_count()
            except (ImportError, AttributeError, TypeError) as e:
                import warnings

                from paimon_tpu.metrics import (
                    MULTIHOST_CONFIG_WARNINGS, global_registry,
                )
                warnings.warn(
                    "multihost.initialize: this jax build does not "
                    f"expose coordination heartbeat tolerance ({e!r});"
                    " peers that outlive a dead host past the default "
                    "~100s window will be aborted by the coordination "
                    "service despite holding valid leases",
                    RuntimeWarning, stacklevel=2)
                global_registry().multihost_metrics().counter(
                    MULTIHOST_CONFIG_WARNINGS).inc()
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id)
    return jax.process_index(), jax.process_count()


def global_mesh(axis_names: Sequence[str] = ("data",),
                shape: Optional[Sequence[int]] = None):
    """A Mesh over ALL devices (every process's chips). With one axis
    the shape is inferred; multi-axis shapes must multiply out to the
    global device count."""
    import jax
    from jax.sharding import Mesh

    devices = np.asarray(jax.devices())
    if shape is None:
        if len(axis_names) != 1:
            raise ValueError("shape is required for a multi-axis mesh")
        shape = (len(devices),)
    if int(np.prod(shape)) != len(devices):
        raise ValueError(f"mesh shape {tuple(shape)} != device count "
                         f"{len(devices)}")
    return Mesh(devices.reshape(shape), tuple(axis_names))


def process_local_batch(mesh, name_to_array, axis: str = "data"):
    """Assemble each process's host-local numpy columns into ONE
    globally sharded array per column: host batches concatenate along
    `axis` across processes without any host gathering the whole batch
    (reference: parallel source readers each feeding their workers).
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    sharding = NamedSharding(mesh, PartitionSpec(axis))
    out = {}
    for name, arr in name_to_array.items():
        arr = np.asarray(arr)
        out[name] = jax.make_array_from_process_local_data(
            sharding, arr)
    return out


def split_weight(split) -> int:
    """A split's assignment weight: on-disk bytes from manifest stats
    (DataFileMeta.file_size sums — available before any file IO, same
    source as parallel/packing.bucket_row_counts).  Objects without
    data_files weigh 1 so plain sequences still round-robin."""
    files = getattr(split, "data_files", None)
    if not files:
        return 1
    return max(1, sum(int(f.file_size) for f in files))


def assign_splits(splits: Sequence, process_index: Optional[int] = None,
                  process_count: Optional[int] = None) -> List:
    """Deterministic byte-size-aware split ownership: splits pack onto
    processes with the same greedy LPT policy as parallel/packing.py,
    keyed on manifest byte sizes — round-robin by index ignored sizes,
    so one host could own every large split while its peers finished
    early and idled at the scan barrier.  Every process computes the
    SAME plan (sort + tie-breaks are total orders over (size, index)),
    reads only its own share, and no coordinator or shuffle is needed
    — the contract of the reference's split enumerator and the torch
    loader's (rank, worker) sharding, unchanged."""
    import jax

    if process_index is None:
        process_index = jax.process_index()
    if process_count is None:
        process_count = jax.process_count()
    if process_count <= 1:
        return list(splits)
    weights = [split_weight(s) for s in splits]
    order = sorted(range(len(splits)),
                   key=lambda i: (-weights[i], i))
    loads = [0] * process_count
    mine: List[int] = []
    for i in order:
        target = min(range(process_count), key=lambda p: (loads[p], p))
        loads[target] += weights[i]
        if target == process_index:
            mine.append(i)
    # preserve plan order within the owned share (stable for callers
    # that zip splits with prior state)
    return [splits[i] for i in sorted(mine)]


def distributed_write_commit_user(base: str = "writer") -> str:
    """Per-process commit user for multi-host writers: processes write
    independently and the snapshot CAS serializes their commits (the
    object-store conditional-PUT / rename-CAS is the only global
    agreement point — reference: committer operator singleton)."""
    import jax

    return f"{base}-p{jax.process_index()}"


# -- agreement primitives (parallel/distributed.py builds on these) ----------

def barrier(name: str = "barrier") -> float:
    """Block until every process reaches this point; returns the wait
    in milliseconds (also recorded in the multihost metric group —
    the direct cost of global agreement).  Single-process: 0ms.

    Deadline-aware like every other blocking wait in the repo
    (utils/deadline.py): a request whose budget is already spent must
    not ENTER a collective it may never leave — the tier-1 lint bans
    direct sync_global_devices / broadcast_one_to_all /
    process_allgather calls outside this module for exactly this
    reason (plus the wait metric)."""
    import jax

    if jax.process_count() == 1:
        return 0.0
    from jax.experimental import multihost_utils

    from paimon_tpu.metrics import (
        MULTIHOST_BARRIER_WAIT_MS, global_registry,
    )
    from paimon_tpu.utils.deadline import check_deadline
    check_deadline(f"multihost barrier {name!r}")
    t0 = _time.perf_counter()
    multihost_utils.sync_global_devices(name)
    waited = (_time.perf_counter() - t0) * 1000
    global_registry().multihost_metrics().histogram(
        MULTIHOST_BARRIER_WAIT_MS).update(waited)
    return waited


def broadcast_value(value: int, root: int = 0) -> int:
    """Agree on one int64 across all processes: `root`'s value wins
    (the "small broadcast" pinning one snapshot id for a
    snapshot-consistent cross-host scan).  Single-process: identity."""
    import jax

    if jax.process_count() == 1:
        return int(value)
    from jax.experimental import multihost_utils

    from paimon_tpu.utils.deadline import check_deadline
    check_deadline("multihost broadcast")
    out = multihost_utils.broadcast_one_to_all(
        np.asarray(int(value), dtype=np.int64),
        is_source=jax.process_index() == root)
    return int(np.asarray(out))


def allgather_bytes(payload: bytes) -> List[bytes]:
    """Every process contributes one bytes payload; every process
    receives ALL of them, indexed by process id.  Two-phase (length
    allgather -> padded uint8 allgather) so payload sizes may differ.
    This is the commit-message wire of coordinator arbitration and the
    row-exchange wire of 'exchange' routing.  Single-process:
    [payload]."""
    import jax

    if jax.process_count() == 1:
        return [bytes(payload)]
    from jax.experimental import multihost_utils

    from paimon_tpu.utils.deadline import check_deadline
    check_deadline("multihost allgather")
    arr = np.frombuffer(bytes(payload), dtype=np.uint8)
    lengths = np.asarray(multihost_utils.process_allgather(
        np.asarray([len(arr)], dtype=np.int64)))
    lengths = lengths.reshape(jax.process_count(), -1)[:, 0]
    max_len = max(1, int(lengths.max()))
    padded = np.zeros(max_len, dtype=np.uint8)
    padded[:len(arr)] = arr
    gathered = np.asarray(multihost_utils.process_allgather(padded))
    gathered = gathered.reshape(jax.process_count(), max_len)
    return [gathered[p, :int(lengths[p])].tobytes()
            for p in range(jax.process_count())]
