"""Pipelined merge-on-read scan executor.

The serial read path walks a plan's splits one by one: download every
data file of split k, decode it to Arrow, merge, only then touch split
k+1 — the object store sits idle while the merge kernel runs and the
merge kernel sits idle while files download.  This module turns that
loop into a bounded producer-consumer pipeline:

    submit ───► [ thread pool: IO + Arrow decode + per-split merge ]
      ▲               │ (Arrow C++ and file IO release the GIL)
      │               ▼
      └── byte budget ◄── iter_split_tables() yields per-split tables

* `scan.split.parallelism` worker threads each run a full
  `read_split` (download → decode → run assembly → merge kernel), so
  split k's merge overlaps split k+1's downloads;
* up to `parallelism + read.prefetch.splits` splits are admitted at
  once, additionally capped by the `read.prefetch.max-bytes` in-flight
  byte budget (estimated as the sum of the split's data-file sizes on
  disk); at least one split is always admitted so a budget smaller
  than one split cannot stall the scan;
* results are yielded in plan order (`ordered=True`, the default — the
  contract batch/streaming reads need) or in completion order
  (`ordered=False`, for loaders that only want throughput);
* transient store faults inside workers ride the parallel/fault.py
  taxonomy + utils/backoff.py retry schedule (read.retry.*) instead of
  aborting the scan — see `read_file_retrying`;
* the pool is shut down (pending work cancelled) when iteration
  completes, raises, or the consumer abandons the generator — no
  leaked executor threads on any path.

Everything that reads splits routes through here: both split readers'
`read_splits` (core/read.py, core/append.py), `TableRead.to_arrow` /
`iter_splits` (table/table.py) and therefore the SQL executor, the
query service and the streaming loaders, plus the jax/torch/ray/daft
integrations.
"""

from __future__ import annotations

import os
from collections import deque
from typing import Callable, Iterator, Optional, Sequence, Tuple

from paimon_tpu.options import CoreOptions

__all__ = ["iter_split_tables", "read_file_retrying",
           "read_fault_is_retryable", "read_or_skip_corrupt",
           "resolve_parallelism"]


def resolve_parallelism(options: Optional[CoreOptions]) -> int:
    """Worker threads for the pipelined scan: scan.split.parallelism,
    defaulting to min(8, cpu count).  1 means serial."""
    par = None
    if options is not None:
        par = options.get(CoreOptions.SCAN_SPLIT_PARALLELISM)
    if par is None:
        par = min(8, os.cpu_count() or 1)
    return max(1, int(par))


def _estimated_bytes(split) -> int:
    """In-flight cost estimate for one split: its on-disk data bytes
    (decoded size is larger; the budget is a throttle, not an
    allocator)."""
    return max(1, sum(f.file_size for f in split.data_files))


def read_fault_is_retryable(exc: BaseException) -> bool:
    """The READ-path refinement of fault.py's taxonomy: transient
    store faults retry, EXCEPT FileNotFoundError — on the read path a
    missing planned file means the snapshot raced maintenance
    (expiry/orphan clean); it cannot reappear, so it keeps the
    pre-pipeline behavior: no retry, and eligible for the
    scan.ignore-corrupt-files skip like any other unreadable file.
    (The compaction plane intentionally differs: its per-bucket ladder
    re-plans on retry, so FileNotFoundError stays transient there.)"""
    from paimon_tpu.parallel.fault import is_transient_error
    return is_transient_error(exc) and \
        not isinstance(exc, FileNotFoundError)


def read_file_retrying(fn: Callable[[], object],
                       options: Optional[CoreOptions],
                       what: str = "data file"):
    """Run one file-granularity read under the read.retry.* schedule.

    Transient store faults (fault.py taxonomy: 503 TransientStoreError,
    OSError IO faults) retry with capped decorrelated-jitter backoff up
    to read.retry.max-attempts total attempts, then re-raise — they are
    NEVER eligible for the scan.ignore-corrupt-files skip, which is
    reserved for genuinely undecodable bytes.  Non-transient errors
    propagate immediately.  FileNotFoundError is excluded from the
    retry: a planned-then-deleted file (racing snapshot expiry /
    orphan clean) cannot reappear, so retrying only burns backoff
    sleeps — it propagates at once and stays in the skip-eligible
    class (see read_fault_is_retryable).
    """
    from paimon_tpu.parallel.fault import is_transient_error
    from paimon_tpu.utils.backoff import Backoff

    if options is not None:
        attempts = options.get(CoreOptions.READ_RETRY_MAX_ATTEMPTS)
        base_ms = options.get(CoreOptions.READ_RETRY_BACKOFF)
    else:
        attempts = CoreOptions.READ_RETRY_MAX_ATTEMPTS.default
        base_ms = CoreOptions.READ_RETRY_BACKOFF.default
    attempts = max(1, attempts)
    backoff = None
    attempt = 0
    while True:
        attempt += 1
        try:
            return fn()
        except Exception as e:      # noqa: BLE001 — reclassified below
            if not read_fault_is_retryable(e) or attempt >= attempts:
                raise
            from paimon_tpu.metrics import (
                SCAN_READ_RETRIES, global_registry,
            )
            global_registry().scan_metrics() \
                .counter(SCAN_READ_RETRIES).inc()
            if backoff is None:
                backoff = Backoff(base_ms)
            from paimon_tpu.obs.trace import span as _span
            with _span("retry.backoff", cat="scan", attempt=attempt,
                       what=what, error=type(e).__name__):
                backoff.pause()


def read_or_skip_corrupt(fn: Callable[[], object],
                         options: Optional[CoreOptions], label: str, *,
                         retry: bool = True):
    """THE read-path fault policy, shared by every split reader so the
    taxonomy can't drift between call sites:

    * transient store faults retry under read.retry.* (skipped with
      retry=False when an inner layer already retries), then re-raise
      — never eligible for the corrupt-file skip;
    * everything else (undecodable bytes, missing planned files) warns
      and returns None under scan.ignore-corrupt-files, else raises.
    """
    try:
        if retry:
            return read_file_retrying(fn, options, what=label)
        return fn()
    except Exception as e:      # noqa: BLE001 — reclassified below
        from paimon_tpu.utils.deadline import DeadlineExceededError
        if isinstance(e, DeadlineExceededError):
            # a spent deadline is neither transient nor corrupt bytes:
            # it must surface as the 504, never be skipped as corrupt
            raise
        if read_fault_is_retryable(e):
            raise
        if options is not None and \
                options.get(CoreOptions.SCAN_IGNORE_CORRUPT_FILES):
            # reference scan.ignore-corrupt-files: warn + skip
            import warnings
            warnings.warn(f"skipping corrupt {label}", RuntimeWarning)
            return None
        raise


def iter_split_tables(read, splits: Sequence,
                      options: Optional[CoreOptions] = None, *,
                      ordered: bool = True,
                      stats: Optional[dict] = None
                      ) -> Iterator[Tuple[int, object, object]]:
    """Yield `(index, split, arrow_table)` through the bounded
    prefetch pipeline.

    `read` is anything with a `read_split(split) -> pa.Table` method
    (MergeFileSplitRead, AppendSplitRead, TableRead); `options`
    defaults to `read.options`.  `stats`, when given, receives
    {"parallelism", "peak_inflight_bytes", "max_inflight_splits",
    "submitted"} for tests/benchmarks.
    """
    from paimon_tpu.obs import trace as _trace

    splits = list(splits)
    if options is None:
        options = getattr(read, "options", None)
    _trace.sync_from_options(options)
    par = resolve_parallelism(options)
    if stats is not None:
        stats.setdefault("parallelism", par)
        stats.setdefault("peak_inflight_bytes", 0)
        stats.setdefault("max_inflight_splits", 0)
        stats.setdefault("submitted", 0)
    if par <= 1 or len(splits) <= 1:
        # serial fast path: no pool, identical to the legacy loop
        from paimon_tpu.utils.deadline import check_deadline
        table_path = getattr(read, "table_path", None)
        for i, s in enumerate(splits):
            check_deadline("scan")
            if stats is not None:
                b = _estimated_bytes(s)
                stats["submitted"] += 1
                stats["peak_inflight_bytes"] = max(
                    stats["peak_inflight_bytes"], b)
                stats["max_inflight_splits"] = max(
                    stats["max_inflight_splits"], 1)
            yield i, s, _read_split_traced(read, s, table_path)
        _trace.maybe_export()
        return
    yield from _iter_pipelined(read, splits, options, par,
                               ordered=ordered, stats=stats)


def _read_split_traced(read, split, table_path):
    """One full split read (IO + decode + merge) under a `scan.split`
    span — the per-worker track whose overlap across workers is the
    pipeline's whole point; IO/decode get their own child spans in
    format/format.py, merge in core/read.py."""
    from paimon_tpu.metrics import SCAN_SPLIT_MS
    from paimon_tpu.obs.trace import span
    with span("scan.split", cat="scan", group="scan",
              metric=SCAN_SPLIT_MS, table=table_path,
              partition=getattr(split, "partition", None),
              bucket=getattr(split, "bucket", None),
              files=len(getattr(split, "data_files", ()))):
        return read.read_split(split)


def _iter_pipelined(read, splits, options, par, *, ordered, stats):
    import concurrent.futures as cf

    from paimon_tpu.metrics import (
        SCAN_PIPELINE_BYTES, SCAN_PIPELINE_SPLITS, global_registry,
    )
    from paimon_tpu.obs import trace as _trace
    from paimon_tpu.obs.trace import span as _span

    if options is not None:
        extra = options.get(CoreOptions.READ_PREFETCH_SPLITS)
        max_bytes = options.get(CoreOptions.READ_PREFETCH_MAX_BYTES)
    else:
        extra = CoreOptions.READ_PREFETCH_SPLITS.default
        max_bytes = CoreOptions.READ_PREFETCH_MAX_BYTES.default
    from paimon_tpu.fs.resilience import is_degraded
    if is_degraded():
        # brownout rung 1+: stop prefetching past the worker pool —
        # shed our own speculative load before shedding requests
        extra = 0
    window = par + max(0, extra)
    max_bytes = max(1, max_bytes)
    group = global_registry().scan_metrics()
    c_splits = group.counter(SCAN_PIPELINE_SPLITS)
    c_bytes = group.counter(SCAN_PIPELINE_BYTES)

    from paimon_tpu.parallel.executors import new_thread_pool
    pool = new_thread_pool(par, "paimon-scan")
    table_path = getattr(read, "table_path", None)
    from paimon_tpu.utils.deadline import (
        DeadlineExceededError, check_deadline, current_deadline,
    )

    inflight = deque()        # [index, split, est_bytes, future]
    inflight_bytes = 0
    next_i = 0
    abandoned = False
    try:
        while inflight or next_i < len(splits):
            # a spent request deadline stops admission AND result
            # waits right here — in-flight workers are abandoned by
            # the finally block (shutdown without join), their results
            # discarded
            check_deadline("scan pipeline")
            # admit work: window + byte budget, always >= 1 in flight
            while next_i < len(splits) and len(inflight) < window and \
                    (not inflight or
                     inflight_bytes + _estimated_bytes(splits[next_i])
                     <= max_bytes):
                s = splits[next_i]
                b = _estimated_bytes(s)
                with _span("scan.admit", cat="scan", split=next_i,
                           bucket=getattr(s, "bucket", None),
                           est_bytes=b):
                    inflight.append(
                        [next_i, s, b,
                         pool.submit(_read_split_traced, read, s,
                                     table_path)])
                inflight_bytes += b
                next_i += 1
                c_splits.inc()
                c_bytes.inc(b)
                if stats is not None:
                    stats["submitted"] += 1
                    stats["peak_inflight_bytes"] = max(
                        stats["peak_inflight_bytes"], inflight_bytes)
                    stats["max_inflight_splits"] = max(
                        stats["max_inflight_splits"], len(inflight))
            dl = current_deadline()
            if ordered:
                # deliberate backpressure: completed-but-unyielded
                # splits hold decoded tables in memory, so they keep
                # counting against the window and byte budget; under
                # head-of-line skew workers may idle rather than let
                # finished results accumulate unboundedly
                idx, s, b, fut = inflight.popleft()
            else:
                cf.wait([e[3] for e in inflight],
                        timeout=None if dl is None
                        else dl.remaining_s(),
                        return_when=cf.FIRST_COMPLETED)
                pos = next((i for i, e in enumerate(inflight)
                            if e[3].done()), None)
                if pos is None:
                    # deadline ran out with every worker still busy:
                    # abandon them all (finally skips the join)
                    abandoned = True
                    raise DeadlineExceededError(
                        "scan pipeline: deadline exceeded waiting "
                        "for any split")
                idx, s, b, fut = inflight[pos]
                del inflight[pos]
            if dl is None:
                # lint-ok: deadline-wait no-deadline branch of an
                # already-deadline-aware wait: the else-branch below
                # bounds with remaining_s() and abandons hung splits
                table = fut.result()  # raises the worker's exception
            else:
                try:
                    table = fut.result(timeout=dl.remaining_s())
                except cf.TimeoutError:
                    # the split read is HUNG past the deadline:
                    # abandon it (no join — the worker drains in the
                    # background, its result discarded)
                    abandoned = True
                    raise DeadlineExceededError(
                        f"scan pipeline: deadline exceeded waiting "
                        f"for split {idx}") from None
            inflight_bytes -= b
            yield idx, s, table
    except GeneratorExit:
        # consumer stopped early (LIMIT satisfied, loader closed):
        # don't block it on in-flight reads whose results are
        # discarded — workers drain in the background and exit
        abandoned = True
        raise
    except DeadlineExceededError:
        # ANY deadline escape (the loop-top check, a worker-side
        # raise surfacing through fut.result) must not join workers
        # that may be hung in store calls — the whole point of the
        # 504 is to answer within one op's grace
        abandoned = True
        raise
    finally:
        # completion, abandonment and worker exceptions all land here:
        # cancel what never started; on completion/raise also join the
        # workers so no threads outlive the read
        for entry in inflight:
            entry[3].cancel()
        pool.shutdown(wait=not abandoned, cancel_futures=True)
        _trace.maybe_export()
