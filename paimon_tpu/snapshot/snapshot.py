"""Snapshot JSON model (version 3).

reference: paimon-api/.../Snapshot.java:43; spec snapshot.md (20 fields).
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Dict, Optional

__all__ = ["Snapshot", "CommitKind", "BATCH_COMMIT_IDENTIFIER"]

CURRENT_VERSION = 3
BATCH_COMMIT_IDENTIFIER = 0x7FFFFFFFFFFFFFFF
LONG_MIN = -(1 << 63)


class CommitKind:
    APPEND = "APPEND"
    COMPACT = "COMPACT"
    OVERWRITE = "OVERWRITE"
    ANALYZE = "ANALYZE"


@dataclass
class Snapshot:
    id: int
    schema_id: int
    base_manifest_list: str
    delta_manifest_list: str
    commit_user: str
    commit_identifier: int
    commit_kind: str
    time_millis: int
    total_record_count: int = 0
    delta_record_count: int = 0
    version: int = CURRENT_VERSION
    base_manifest_list_size: Optional[int] = None
    delta_manifest_list_size: Optional[int] = None
    changelog_manifest_list: Optional[str] = None
    changelog_manifest_list_size: Optional[int] = None
    index_manifest: Optional[str] = None
    changelog_record_count: Optional[int] = None
    watermark: Optional[int] = None
    statistics: Optional[str] = None
    log_offsets: Optional[Dict[str, int]] = None
    properties: Optional[Dict[str, str]] = None
    next_row_id: Optional[int] = None
    operation: Optional[str] = None

    def to_json(self) -> str:
        d = {
            "version": self.version,
            "id": self.id,
            "schemaId": self.schema_id,
            "baseManifestList": self.base_manifest_list,
            "deltaManifestList": self.delta_manifest_list,
            "commitUser": self.commit_user,
            "commitIdentifier": self.commit_identifier,
            "commitKind": self.commit_kind,
            "timeMillis": self.time_millis,
            "totalRecordCount": self.total_record_count,
            "deltaRecordCount": self.delta_record_count,
        }
        opt = {
            "baseManifestListSize": self.base_manifest_list_size,
            "deltaManifestListSize": self.delta_manifest_list_size,
            "changelogManifestList": self.changelog_manifest_list,
            "changelogManifestListSize": self.changelog_manifest_list_size,
            "indexManifest": self.index_manifest,
            "changelogRecordCount": self.changelog_record_count,
            "watermark": self.watermark,
            "statistics": self.statistics,
            "logOffsets": self.log_offsets,
            "properties": self.properties,
            "nextRowId": self.next_row_id,
            "operation": self.operation,
        }
        for k, v in opt.items():
            if v is not None:
                d[k] = v
        return json.dumps(d, indent=2)

    @staticmethod
    def from_json(s: str) -> "Snapshot":
        d = json.loads(s)
        return Snapshot(
            id=d["id"],
            schema_id=d["schemaId"],
            base_manifest_list=d["baseManifestList"],
            delta_manifest_list=d["deltaManifestList"],
            commit_user=d["commitUser"],
            commit_identifier=d["commitIdentifier"],
            commit_kind=d["commitKind"],
            time_millis=d["timeMillis"],
            total_record_count=d.get("totalRecordCount", 0),
            delta_record_count=d.get("deltaRecordCount", 0),
            version=d.get("version", CURRENT_VERSION),
            base_manifest_list_size=d.get("baseManifestListSize"),
            delta_manifest_list_size=d.get("deltaManifestListSize"),
            changelog_manifest_list=d.get("changelogManifestList"),
            changelog_manifest_list_size=d.get("changelogManifestListSize"),
            index_manifest=d.get("indexManifest"),
            changelog_record_count=d.get("changelogRecordCount"),
            watermark=d.get("watermark"),
            statistics=d.get("statistics"),
            log_offsets=d.get("logOffsets"),
            properties=d.get("properties"),
            next_row_id=d.get("nextRowId"),
            operation=d.get("operation"),
        )
