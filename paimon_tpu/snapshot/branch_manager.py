"""BranchManager: isolated dev branches under ``branch/branch-<name>/``.

reference: paimon-core/.../utils/BranchManager.java +
FileSystemBranchManager: a branch copies the source schema + optionally a
tagged snapshot, then evolves its own snapshot/ and schema/ dirs;
fast-forward replays branch snapshots onto main.
"""

from __future__ import annotations

from typing import List, Optional

from paimon_tpu.fs import FileIO
from paimon_tpu.snapshot.snapshot import Snapshot

__all__ = ["BranchManager"]

BRANCH_PREFIX = "branch-"
DEFAULT_MAIN_BRANCH = "main"


class BranchManager:
    def __init__(self, file_io: FileIO, table_path: str):
        self.file_io = file_io
        self.table_path = table_path.rstrip("/")

    @property
    def branch_dir(self) -> str:
        return f"{self.table_path}/branch"

    def branch_path(self, name: str) -> str:
        return f"{self.branch_dir}/{BRANCH_PREFIX}{name}"

    def branch_exists(self, name: str) -> bool:
        if name == DEFAULT_MAIN_BRANCH:
            return True
        return self.file_io.exists(self.branch_path(name))

    def branches(self) -> List[str]:
        out = []
        for st in self.file_io.list_status(self.branch_dir):
            fname = st.path.rstrip("/").split("/")[-1]
            if fname.startswith(BRANCH_PREFIX):
                out.append(fname[len(BRANCH_PREFIX):])
        return sorted(out)

    def create_branch(self, name: str,
                      from_snapshot: Optional[Snapshot] = None,
                      schema_json: Optional[str] = None):
        """Create branch, copying latest schema (and optionally pinning a
        snapshot as the branch's first)."""
        if name == DEFAULT_MAIN_BRANCH or self.branch_exists(name):
            raise ValueError(f"Branch {name!r} already exists")
        root = self.branch_path(name)
        if schema_json is None:
            # copy latest schema from main
            from paimon_tpu.schema.schema_manager import SchemaManager
            sm = SchemaManager(self.file_io, self.table_path)
            latest = sm.latest()
            if latest is None:
                raise ValueError("Cannot branch a table with no schema")
            schema_json = latest.to_json()
            schema_id = latest.id
        else:
            import json as _json
            schema_id = _json.loads(schema_json)["id"]
        self.file_io.write_bytes(f"{root}/schema/schema-{schema_id}",
                                 schema_json.encode("utf-8"),
                                 overwrite=False)
        if from_snapshot is not None:
            self.file_io.write_bytes(
                f"{root}/snapshot/snapshot-{from_snapshot.id}",
                from_snapshot.to_json().encode("utf-8"), overwrite=False)
            self.file_io.write_utf8(f"{root}/snapshot/LATEST",
                                    str(from_snapshot.id))
            self.file_io.write_utf8(f"{root}/snapshot/EARLIEST",
                                    str(from_snapshot.id))

    def drop_branch(self, name: str):
        self.file_io.delete(self.branch_path(name), recursive=True)

    def rename_branch(self, old: str, new: str):
        """Directory rename preserving every branch file verbatim
        (reference RenameBranchProcedure)."""
        if old == DEFAULT_MAIN_BRANCH:
            raise ValueError("cannot rename the main branch")
        if not self.branch_exists(old):
            raise ValueError(f"Branch {old!r} not found")
        if new == DEFAULT_MAIN_BRANCH or self.branch_exists(new):
            raise ValueError(f"Branch {new!r} already exists")
        if not self.file_io.rename(self.branch_path(old),
                                   self.branch_path(new)):
            raise RuntimeError(f"renaming branch {old!r} failed")

    def fast_forward(self, name: str):
        """Replace main's snapshots with the branch's (reference
        BranchManager.fastForward)."""
        from paimon_tpu.snapshot.snapshot_manager import SnapshotManager
        branch_sm = SnapshotManager(self.file_io, self.table_path,
                                    branch=name)
        main_sm = SnapshotManager(self.file_io, self.table_path)
        branch_earliest = branch_sm.earliest_snapshot_id()
        if branch_earliest is None:
            raise ValueError(f"Branch {name!r} has no snapshots")
        # delete main snapshots >= branch earliest, then copy branch files
        main_latest = main_sm.latest_snapshot_id()
        if main_latest is not None:
            for i in range(branch_earliest, main_latest + 1):
                main_sm.delete_snapshot(i)
        latest = None
        for snap in branch_sm.snapshots():
            self.file_io.write_bytes(main_sm.snapshot_path(snap.id),
                                     snap.to_json().encode("utf-8"))
            latest = snap.id
        if latest is not None:
            main_sm.commit_latest_hint(latest)
        # copy branch schemas not present on main
        from paimon_tpu.schema.schema_manager import SchemaManager
        branch_schemas = SchemaManager(self.file_io, self.table_path,
                                       branch=name)
        main_schemas = SchemaManager(self.file_io, self.table_path)
        main_ids = set(main_schemas.list_all_ids())
        for sid in branch_schemas.list_all_ids():
            if sid not in main_ids:
                self.file_io.write_bytes(
                    main_schemas.schema_path(sid),
                    branch_schemas.schema(sid).to_json().encode("utf-8"))
