"""Snapshot/refs subsystem: snapshot JSON files, snapshot manager,
tags, branches, consumers.

reference: paimon-api/.../Snapshot.java:43, paimon-core/.../utils/
(SnapshotManager, TagManager, BranchManager, ChangelogManager), consumer/.
"""

from paimon_tpu.snapshot.snapshot import Snapshot, CommitKind  # noqa: F401
from paimon_tpu.snapshot.snapshot_manager import SnapshotManager  # noqa: F401
from paimon_tpu.snapshot.tag_manager import TagManager  # noqa: F401
from paimon_tpu.snapshot.branch_manager import BranchManager  # noqa: F401
from paimon_tpu.snapshot.consumer_manager import ConsumerManager  # noqa: F401
