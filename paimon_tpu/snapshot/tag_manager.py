"""TagManager: named immutable refs to snapshots (``tag/tag-<name>``).

reference: paimon-core/.../utils/TagManager.java; a tag file stores the
snapshot JSON it pins, protecting its files from expiry.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from paimon_tpu.fs import FileIO
from paimon_tpu.snapshot.snapshot import Snapshot

__all__ = ["TagManager"]

TAG_PREFIX = "tag-"


class TagManager:
    def __init__(self, file_io: FileIO, table_path: str):
        self.file_io = file_io
        self.table_path = table_path.rstrip("/")

    @property
    def tag_dir(self) -> str:
        return f"{self.table_path}/tag"

    def tag_path(self, name: str) -> str:
        return f"{self.tag_dir}/{TAG_PREFIX}{name}"

    def create_tag(self, snapshot: Snapshot, name: str,
                   ignore_if_exists: bool = False,
                   time_retained_ms=None):
        """`time_retained_ms`: the tag self-expires after this age
        (reference tag/Tag.java tagCreateTime + tagTimeRetained; the
        expiry sweep is `expire_tags`)."""
        if self.tag_exists(name):
            if ignore_if_exists:
                return
            raise ValueError(f"Tag {name!r} already exists")
        payload = snapshot.to_json()
        if time_retained_ms is not None:
            import json as _json
            import time as _time
            d = _json.loads(payload)
            d["tagCreateTime"] = int(_time.time() * 1000)
            d["tagTimeRetained"] = int(time_retained_ms)
            payload = _json.dumps(d)
        ok = self.file_io.try_to_write_atomic(
            self.tag_path(name), payload.encode("utf-8"))
        if not ok:
            raise ValueError(f"Tag {name!r} already exists")

    def expire_tags(self, now_ms=None) -> list:
        """Delete tags whose tagCreateTime + tagTimeRetained has
        passed; returns the names removed (reference
        TagTimeExpire.java)."""
        import json as _json
        import time as _time
        now_ms = now_ms if now_ms is not None else int(_time.time()
                                                       * 1000)
        removed = []
        for st in self.file_io.list_status(self.tag_dir):
            fname = st.path.rstrip("/").split("/")[-1]
            if not fname.startswith(TAG_PREFIX):
                continue
            name = fname[len(TAG_PREFIX):]
            try:
                d = _json.loads(self.file_io.read_utf8(
                    self.tag_path(name)))
            except (FileNotFoundError, OSError, ValueError):
                continue
            created = d.get("tagCreateTime")
            retained = d.get("tagTimeRetained")
            if created is not None and retained is not None and \
                    created + retained <= now_ms:
                self.delete_tag(name)
                removed.append(name)
        return removed

    def rename_tag(self, old: str, new: str):
        """Byte-preserving rename (reference TagManager.renameTag) —
        keeps tagCreateTime/tagTimeRetained, which a parse-and-rewrite
        would drop."""
        if not self.tag_exists(old):
            raise FileNotFoundError(f"Tag {old!r} not found")
        if self.tag_exists(new):
            raise ValueError(f"Tag {new!r} already exists")
        if not self.file_io.rename(self.tag_path(old),
                                   self.tag_path(new)):
            raise RuntimeError(f"renaming tag {old!r} failed")

    def delete_tag(self, name: str):
        self.file_io.delete_quietly(self.tag_path(name))

    def tag_exists(self, name: str) -> bool:
        return self.file_io.exists(self.tag_path(name))

    def get_tag(self, name: str) -> Snapshot:
        return Snapshot.from_json(self.file_io.read_utf8(self.tag_path(name)))

    def tags(self) -> Dict[str, Snapshot]:
        out = {}
        for st in self.file_io.list_status(self.tag_dir):
            fname = st.path.rstrip("/").split("/")[-1]
            if fname.startswith(TAG_PREFIX):
                name = fname[len(TAG_PREFIX):]
                out[name] = self.get_tag(name)
        return dict(sorted(out.items(), key=lambda kv: kv[1].id))

    def tagged_snapshots(self) -> List[Snapshot]:
        return list(self.tags().values())
