"""Decoupled long-lived changelog.

reference: paimon-core/src/main/java/org/apache/paimon/utils/
ChangelogManager.java + Changelog.java: changelog retention can outlive
snapshot retention — when an expiring snapshot carries changelog, its
metadata is preserved under `changelog/changelog-<id>` so the changelog
files stay readable for stream consumers long after the snapshot (and
its data files) are gone.  `changelog.num-retained.{min,max}` /
`changelog.time-retained` bound the decoupled set; an expire pass
deletes the oldest entries together with their changelog files.
"""

from __future__ import annotations

from typing import Iterator, List, Optional

from paimon_tpu.fs import FileIO
from paimon_tpu.snapshot.snapshot import Snapshot

__all__ = ["ChangelogManager"]

CHANGELOG_PREFIX = "changelog-"
EARLIEST = "EARLIEST"
LATEST = "LATEST"


class ChangelogManager:
    def __init__(self, file_io: FileIO, table_path: str,
                 branch: str = "main"):
        self.file_io = file_io
        self.table_path = table_path.rstrip("/")
        self.branch = branch or "main"

    @property
    def changelog_dir(self) -> str:
        if self.branch != "main":
            return (f"{self.table_path}/branch/branch-{self.branch}"
                    f"/changelog")
        return f"{self.table_path}/changelog"

    def changelog_path(self, changelog_id: int) -> str:
        return f"{self.changelog_dir}/{CHANGELOG_PREFIX}{changelog_id}"

    # -- reads ---------------------------------------------------------------

    def changelog(self, changelog_id: int) -> Snapshot:
        return Snapshot.from_json(self.file_io.read_utf8(
            self.changelog_path(changelog_id)))

    def try_changelog(self, changelog_id: int) -> Optional[Snapshot]:
        try:
            return self.changelog(changelog_id)
        except (FileNotFoundError, OSError):
            return None

    def _ids(self) -> List[int]:
        try:
            names = self.file_io.list_files(self.changelog_dir)
        except (FileNotFoundError, OSError):
            return []
        out = []
        for n in names:
            base = n.rsplit("/", 1)[-1]
            if base.startswith(CHANGELOG_PREFIX):
                try:
                    out.append(int(base[len(CHANGELOG_PREFIX):]))
                except ValueError:
                    continue
        return sorted(out)

    def earliest_changelog_id(self) -> Optional[int]:
        ids = self._ids()
        return ids[0] if ids else None

    def latest_changelog_id(self) -> Optional[int]:
        ids = self._ids()
        return ids[-1] if ids else None

    def changelogs(self) -> Iterator[Snapshot]:
        for cid in self._ids():
            snap = self.try_changelog(cid)
            if snap is not None:
                yield snap

    # -- writes --------------------------------------------------------------

    def commit_changelog(self, snapshot: Snapshot) -> bool:
        """Preserve an expiring snapshot's changelog metadata (reference
        ChangelogManager.commitChangelog)."""
        path = self.changelog_path(snapshot.id)
        if self.file_io.exists(path):
            return False
        return self.file_io.try_to_write_atomic(
            path, snapshot.to_json().encode("utf-8"))

    def delete_changelog(self, changelog_id: int):
        self.file_io.delete_quietly(self.changelog_path(changelog_id))
