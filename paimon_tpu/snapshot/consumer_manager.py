"""ConsumerManager: per-consumer-id streaming progress (``consumer/``).

reference: paimon-core/.../consumer/ConsumerManager.java -- a consumer file
records next-snapshot for a streaming reader; protects snapshots from
expiry and lets readers resume.
"""

from __future__ import annotations

import json
import time as _time
from typing import Dict, Optional

from paimon_tpu.fs import FileIO

__all__ = ["ConsumerManager"]

CONSUMER_PREFIX = "consumer-"


class ConsumerManager:
    def __init__(self, file_io: FileIO, table_path: str):
        self.file_io = file_io
        self.table_path = table_path.rstrip("/")

    @property
    def consumer_dir(self) -> str:
        return f"{self.table_path}/consumer"

    def consumer_path(self, consumer_id: str) -> str:
        return f"{self.consumer_dir}/{CONSUMER_PREFIX}{consumer_id}"

    def consumer(self, consumer_id: str) -> Optional[int]:
        path = self.consumer_path(consumer_id)
        if not self.file_io.exists(path):
            return None
        return json.loads(self.file_io.read_utf8(path))["nextSnapshot"]

    def record_consumer(self, consumer_id: str, next_snapshot: int):
        self.file_io.write_utf8(
            self.consumer_path(consumer_id),
            json.dumps({"nextSnapshot": next_snapshot,
                        "lastModified": int(_time.time() * 1000)}))

    def delete_consumer(self, consumer_id: str):
        self.file_io.delete_quietly(self.consumer_path(consumer_id))

    def consumers(self) -> Dict[str, int]:
        out = {}
        for st in self.file_io.list_status(self.consumer_dir):
            fname = st.path.rstrip("/").split("/")[-1]
            if fname.startswith(CONSUMER_PREFIX):
                cid = fname[len(CONSUMER_PREFIX):]
                v = self.consumer(cid)
                if v is not None:
                    out[cid] = v
        return out

    def min_next_snapshot(self) -> Optional[int]:
        """Smallest consumer progress -- lower bound protected from expiry."""
        vals = self.consumers().values()
        return min(vals) if vals else None

    def expire_stale(self, expire_ms: int):
        now = int(_time.time() * 1000)
        for st in self.file_io.list_status(self.consumer_dir):
            fname = st.path.rstrip("/").split("/")[-1]
            if not fname.startswith(CONSUMER_PREFIX):
                continue
            try:
                d = json.loads(self.file_io.read_utf8(st.path))
                if now - d.get("lastModified", now) > expire_ms:
                    self.file_io.delete_quietly(st.path)
            except (OSError, ValueError):
                pass
