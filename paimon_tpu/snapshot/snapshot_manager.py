"""SnapshotManager: list/find/commit snapshot files with hint files.

reference: paimon-core/.../utils/SnapshotManager.java (snapshot/snapshot-N,
EARLIEST/LATEST hints that may be stale; full scan as fallback).

Latest-snapshot cache (tail-tolerance PR satellite, ROADMAP item 5
residual): one commit used to pay ~5 `latest_snapshot()` walks, each
2-3 store round trips (hint read + exists probe + forward walk +
snapshot JSON read) — the chain that kept small-batch ingest
latency-bound.  A validated per-manager cache cuts each walk to 1-2
`exists` probes: the cached id N is trusted iff snapshot-(N+1) is
absent AND snapshot-N still exists (guards external rollback), and a
newer commit just walks forward FROM the cache instead of from the
hint.  Invalidation is CAS-bumped: `try_commit` advances the cache on
a win AND on a loss (the contested id provably exists — the winner
wrote it), `delete_snapshot` of the cached tip drops it.  Correctness
never depends on the cache: every path re-probes the store before
answering, so a stale cache costs round trips, not wrong answers.
"""

from __future__ import annotations

import threading
from typing import Iterator, List, Optional

from paimon_tpu.fs import FileIO
from paimon_tpu.snapshot.snapshot import Snapshot

__all__ = ["SnapshotManager"]

SNAPSHOT_PREFIX = "snapshot-"
EARLIEST = "EARLIEST"
LATEST = "LATEST"
# JSON list of snapshot ids FOLDED out of the middle of the chain by
# expire_snapshots' heartbeat-folding pass (maintenance/expire.py):
# readers treat these ids as legitimately absent (fsck excuses them
# from snapshot-gap), and the list self-prunes below EARLIEST
FOLDED = "FOLDED"


class SnapshotManager:
    def __init__(self, file_io: FileIO, table_path: str,
                 branch: str = "main"):
        self.file_io = file_io
        self.table_path = table_path.rstrip("/")
        self.branch = branch or "main"
        self._cache_lock = threading.Lock()
        # id-ONLY cache, deliberately: rollback_to / fast_forward can
        # delete and RECREATE a snapshot id with different content
        # (even bypassing this manager — fast_forward writes through a
        # fresh one), so the tip's JSON is re-read on every
        # latest_snapshot(); only the walk to FIND the tip is cached
        self._cached_latest_id: Optional[int] = None

    @property
    def snapshot_dir(self) -> str:
        if self.branch != "main":
            return (f"{self.table_path}/branch/branch-{self.branch}"
                    f"/snapshot")
        return f"{self.table_path}/snapshot"

    def snapshot_path(self, snapshot_id: int) -> str:
        return f"{self.snapshot_dir}/{SNAPSHOT_PREFIX}{snapshot_id}"

    # -- reads ---------------------------------------------------------------

    def snapshot(self, snapshot_id: int) -> Snapshot:
        return Snapshot.from_json(
            self.file_io.read_utf8(self.snapshot_path(snapshot_id)))

    def snapshot_exists(self, snapshot_id: int) -> bool:
        return self.file_io.exists(self.snapshot_path(snapshot_id))

    def _hint(self, name: str) -> Optional[int]:
        path = f"{self.snapshot_dir}/{name}"
        try:
            if self.file_io.exists(path):
                return int(self.file_io.read_utf8(path).strip())
        except (ValueError, OSError):
            pass
        return None

    def _all_ids(self) -> List[int]:
        ids = []
        for st in self.file_io.list_status(self.snapshot_dir):
            name = st.path.rstrip("/").split("/")[-1]
            if name.startswith(SNAPSHOT_PREFIX):
                try:
                    ids.append(int(name[len(SNAPSHOT_PREFIX):]))
                except ValueError:
                    pass
        return sorted(ids)

    def earliest_snapshot_id(self) -> Optional[int]:
        hint = self._hint(EARLIEST)
        if hint is not None and self.snapshot_exists(hint):
            # hint may be stale upward (expired snapshots); walk forward
            i = hint
            while not self.snapshot_exists(i):
                i += 1
            return i
        ids = self._all_ids()
        return ids[0] if ids else None

    def _note_latest(self, snapshot_id: int):
        with self._cache_lock:
            self._cached_latest_id = snapshot_id

    def _invalidate_latest(self):
        with self._cache_lock:
            self._cached_latest_id = None

    def latest_snapshot_id(self) -> Optional[int]:
        with self._cache_lock:
            cached = self._cached_latest_id
        if cached is not None:
            if not self.snapshot_exists(cached + 1):
                if self.snapshot_exists(cached):
                    return cached           # 2 probes, no hint read
                # the cached tip vanished (external rollback): fall
                # back to the full hint path below
                self._invalidate_latest()
            else:
                # a newer commit landed: walk forward FROM the cache
                i = cached + 1
                while self.snapshot_exists(i + 1):
                    i += 1
                self._note_latest(i)
                return i
        hint = self._hint(LATEST)
        if hint is not None and self.snapshot_exists(hint):
            # hint may be stale downward (newer commits); walk forward
            i = hint
            while self.snapshot_exists(i + 1):
                i += 1
            self._note_latest(i)
            return i
        ids = self._all_ids()
        if ids:
            self._note_latest(ids[-1])
            return ids[-1]
        return None

    def latest_snapshot(self) -> Optional[Snapshot]:
        sid = self.latest_snapshot_id()
        return self.snapshot(sid) if sid is not None else None

    def snapshots(self) -> Iterator[Snapshot]:
        earliest = self.earliest_snapshot_id()
        latest = self.latest_snapshot_id()
        if earliest is None or latest is None:
            return
        for i in range(earliest, latest + 1):
            if self.snapshot_exists(i):
                yield self.snapshot(i)

    def snapshot_count(self) -> int:
        return sum(1 for _ in self.snapshots())

    def earlier_or_equal_time_mills(self,
                                    time_millis: int) -> Optional[Snapshot]:
        """Latest snapshot with timeMillis <= given (reference
        SnapshotManager.earlierOrEqualTimeMills); binary search over
        ids, probing downward past folded-heartbeat holes."""
        lo = self.earliest_snapshot_id()
        hi = self.latest_snapshot_id()
        if lo is None or hi is None:
            return None
        best = None
        while lo <= hi:
            mid = (lo + hi) // 2
            probe = mid
            while probe >= lo and not self.snapshot_exists(probe):
                probe -= 1          # folded hole: nearest older id
            if probe < lo:
                lo = mid + 1
                continue
            s = self.snapshot(probe)
            if s.time_millis <= time_millis:
                best = s
                lo = mid + 1
            else:
                hi = probe - 1
        return best

    # -- folded-heartbeat bookkeeping ----------------------------------------

    def folded_ids(self) -> set:
        """Ids deliberately removed from the middle of the chain by
        the heartbeat-folding pass; missing/corrupt file = empty."""
        path = f"{self.snapshot_dir}/{FOLDED}"
        try:
            if not self.file_io.exists(path):
                return set()
            import json
            raw = json.loads(self.file_io.read_utf8(path))
            return {int(i) for i in raw}
        except (OSError, ValueError, TypeError):
            return set()

    def record_folded(self, ids) -> None:
        """Durably record ids about to be folded — written BEFORE the
        snapshot files are deleted, so a crash between the two leaves
        ids that are folded-but-present (harmless: the excuse only
        matters for ids that are actually missing).  Self-prunes
        entries below the earliest retained snapshot, whose absence
        needs no excuse."""
        merged = self.folded_ids() | {int(i) for i in ids}
        earliest = self.earliest_snapshot_id()
        if earliest is not None:
            merged = {i for i in merged if i >= earliest}
        import json
        self.file_io.write_utf8(f"{self.snapshot_dir}/{FOLDED}",
                                json.dumps(sorted(merged)),
                                overwrite=True)

    # -- writes --------------------------------------------------------------

    def try_commit(self, snapshot: Snapshot) -> bool:
        """Atomically publish snapshot-N; False if id taken (CAS).
        Both outcomes BUMP the latest cache: a win makes `snapshot`
        the tip, a loss proves the contested id exists (the winner
        wrote it), so the next walk starts there instead of at the
        hint."""
        ok = self.file_io.try_to_write_atomic(
            self.snapshot_path(snapshot.id),
            snapshot.to_json().encode("utf-8"))
        if ok:
            self._note_latest(snapshot.id)
            self.commit_latest_hint(snapshot.id)
            if snapshot.id == 1 or self._hint(EARLIEST) is None:
                self.commit_earliest_hint(snapshot.id)
        else:
            self._note_latest(snapshot.id)
        return ok

    def commit_latest_hint(self, snapshot_id: int):
        self._write_hint(LATEST, snapshot_id)

    def commit_earliest_hint(self, snapshot_id: int):
        self._write_hint(EARLIEST, snapshot_id)

    def _write_hint(self, name: str, snapshot_id: int):
        try:
            self.file_io.write_utf8(f"{self.snapshot_dir}/{name}",
                                    str(snapshot_id), overwrite=True)
        except OSError:
            pass  # hints are best-effort

    def delete_snapshot(self, snapshot_id: int):
        with self._cache_lock:
            if self._cached_latest_id is not None and \
                    snapshot_id >= self._cached_latest_id:
                # rollback at/past the cached tip (expiry only deletes
                # OLD snapshots, which never affect the latest cache)
                self._cached_latest_id = None
        self.file_io.delete_quietly(self.snapshot_path(snapshot_id))
