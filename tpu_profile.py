"""One-shot TPU profile: where does merge time go on the tunneled chip?
Measures H2D bandwidth, multi-operand sort time, winner-select time,
D2H, and MXU sanity.  Run as the ONLY TPU client."""

import time

import numpy as np


def timeit(label, fn, n=3):
    import jax
    # first call includes compile; report both
    t0 = time.perf_counter()
    out = fn()
    jax.block_until_ready(out)
    first = time.perf_counter() - t0
    best = float("inf")
    for _ in range(n):
        t0 = time.perf_counter()
        out = fn()
        jax.block_until_ready(out)
        best = min(best, time.perf_counter() - t0)
    print(f"{label}: first={first:.3f}s best={best:.3f}s", flush=True)
    return out, best


def main():
    import jax
    import jax.numpy as jnp

    print("backend:", jax.default_backend(), flush=True)

    # --- H2D bandwidth ---
    for mb in (64, 256):
        arr = np.random.randint(0, 1 << 30, (mb << 20) // 4,
                                dtype=np.int32)
        t0 = time.perf_counter()
        d = jax.device_put(arr)
        d.block_until_ready()
        dt = time.perf_counter() - t0
        print(f"h2d {mb}MB: {dt:.3f}s = {mb / dt:.0f} MB/s", flush=True)
    # --- D2H ---
    t0 = time.perf_counter()
    _ = np.asarray(d)
    dt = time.perf_counter() - t0
    print(f"d2h 256MB: {dt:.3f}s = {256 / dt:.0f} MB/s", flush=True)

    # --- MXU sanity: bf16 matmul ---
    a = jnp.ones((8192, 8192), jnp.bfloat16)

    @jax.jit
    def mm(a):
        return a @ a

    _, best = timeit("matmul 8192^3 bf16", lambda: mm(a))
    print(f"  -> {2 * 8192**3 / best / 1e12:.1f} TFLOP/s", flush=True)

    # --- the merge plane's actual shape: 16M padded rows, 3 lanes ---
    n = 1 << 24
    lanes = [jnp.asarray(np.random.randint(0, 1 << 31, n, np.uint32))
             for _ in range(3)]
    seq_hi = jnp.zeros(n, jnp.uint32)
    seq_lo = jnp.asarray(np.arange(n, dtype=np.uint32))
    inv = jnp.zeros(n, jnp.uint32)

    @jax.jit
    def sort_only(lanes, seq_hi, seq_lo, inv):
        import jax.lax as lax
        n_ = lanes[0].shape[0]
        iota = lax.iota(jnp.uint32, n_)
        ops = [inv] + list(lanes) + [seq_hi, seq_lo, iota]
        out = lax.sort(tuple(ops), num_keys=len(ops) - 1)
        return out[-1]

    timeit("lax.sort 16M x (6 keys)", lambda: sort_only(
        lanes, seq_hi, seq_lo, inv))

    # packed 2-lane -> u64 single-key variant
    @jax.jit
    def sort_packed(l0, l1, seq):
        import jax.lax as lax
        n_ = l0.shape[0]
        key = (l0.astype(jnp.uint64) << 32) | l1.astype(jnp.uint64)
        iota = lax.iota(jnp.uint32, n_)
        out = lax.sort((key, seq, iota), num_keys=2)
        return out[-1]

    seq64 = jnp.asarray(np.arange(n, dtype=np.uint64))
    timeit("lax.sort 16M packed u64+seq",
           lambda: sort_packed(lanes[0], lanes[1], seq64))

    # full device_sorted_winners end-to-end (incl. transfers both ways)
    from paimon_tpu.ops.merge import device_sorted_winners
    lanes_np = np.stack([np.asarray(x) for x in lanes[:2]], axis=1)
    seq_np = np.arange(n, dtype=np.int64)
    t0 = time.perf_counter()
    perm, winner, prev = device_sorted_winners(lanes_np, seq_np, "last")
    dt = time.perf_counter() - t0
    print(f"device_sorted_winners 16M e2e first: {dt:.3f}s "
          f"({n / dt / 1e6:.2f}M rows/s)", flush=True)
    t0 = time.perf_counter()
    perm, winner, prev = device_sorted_winners(lanes_np, seq_np, "last")
    dt = time.perf_counter() - t0
    print(f"device_sorted_winners 16M e2e warm: {dt:.3f}s "
          f"({n / dt / 1e6:.2f}M rows/s)", flush=True)


if __name__ == "__main__":
    main()
