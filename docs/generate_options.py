"""Options reference generator — the analog of the reference's
paimon-docs plane (auto-generated HTML tables under
`docs/layouts/.../generated/core_configuration.html`, built by
`paimon-docs/.../ConfigOptionsDocGenerator.java`).

Usage:
    python docs/generate_options.py          # rewrites docs/options.md
    python docs/generate_options.py --check  # exit 1 if out of date
"""

import inspect
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from paimon_tpu.options import ConfigOption, CoreOptions  # noqa: E402

OUT = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                   "options.md")


def _type_name(opt: ConfigOption) -> str:
    t = opt.typ
    name = getattr(t, "__name__", str(t))
    return {
        "_parse_bool": "boolean",
        "_parse_duration_ms": "duration (ms)",
        "parse_memory_size": "memory size (bytes)",
        "str": "string", "int": "int", "float": "float",
    }.get(name, name)


def _default_repr(opt: ConfigOption) -> str:
    d = opt.default
    if d is None:
        return "(none)"
    if isinstance(d, bool):
        return "true" if d else "false"
    return str(d)


def duplicate_option_keys(src: str):
    """Option keys declared more than once in a CoreOptions source body.

    Duplicates with the SAME attribute name collapse in the class dict
    (the second silently wins), so only source-level scanning can catch
    them — exactly the `manifest.target-file-size` double declaration
    this guards against.  Returns the sorted list of offending keys."""
    import re
    keys = re.findall(
        r"=\s*ConfigOption\(\s*[\r\n ]*[\"']([^\"']+)[\"']", src)
    seen, dups = set(), set()
    for k in keys:
        (dups if k in seen else seen).add(k)
    return sorted(dups)


def collect():
    """All ConfigOptions declared on CoreOptions, in declaration order.

    Refuses to run (and so fails the docs drift test) when any option
    key is declared twice."""
    src = inspect.getsource(CoreOptions)
    dups = duplicate_option_keys(src)
    if dups:
        raise SystemExit(
            f"duplicated option key(s) in CoreOptions: {', '.join(dups)}")
    order = {}
    for name, val in vars(CoreOptions).items():
        if isinstance(val, ConfigOption):
            order[name] = src.index(f"{name} ")
    return [vars(CoreOptions)[n]
            for n in sorted(order, key=order.get)]


def render() -> str:
    opts = collect()
    lines = [
        "# Configuration options",
        "",
        "Auto-generated from `paimon_tpu/options.py` by "
        "`docs/generate_options.py` — do not edit by hand.",
        "",
        f"{len(opts)} options. Keys match the reference's "
        "`CoreOptions.java` where the option exists there; keys under "
        "`tpu.*` are this framework's own.",
        "",
        "| Key | Type | Default | Description |",
        "|---|---|---|---|",
    ]
    for o in opts:
        desc = (o.description or "").replace("|", "\\|").replace("\n", " ")
        lines.append(f"| `{o.key}` | {_type_name(o)} "
                     f"| {_default_repr(o)} | {desc} |")
    return "\n".join(lines) + "\n"


def main():
    text = render()
    if "--check" in sys.argv:
        current = open(OUT).read() if os.path.exists(OUT) else ""
        if current != text:
            sys.stderr.write("docs/options.md is out of date; run "
                             "python docs/generate_options.py\n")
            return 1
        return 0
    with open(OUT, "w") as f:
        f.write(text)
    print(f"wrote {OUT} ({text.count(chr(10))} lines)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
