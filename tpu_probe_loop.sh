#!/bin/bash
# patient probe: the axon tunnel wedges and un-wedges on its own;
# retry the link profile until it succeeds, then stop.
for i in $(seq 1 12); do
  echo "=== attempt $i $(date +%H:%M:%S) ===" >> /tmp/tpu_probe.log
  timeout 600 python -u /root/repo/tpu_link_probe.py >> /tmp/tpu_probe.log 2>&1
  rc=$?
  echo "=== rc=$rc ===" >> /tmp/tpu_probe.log
  if [ $rc -eq 0 ]; then exit 0; fi
  sleep 120
done
exit 1
